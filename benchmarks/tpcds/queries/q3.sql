SELECT d_year, i_brand_id AS brand_id, i_brand AS brand,
       sum(ss_ext_sales_price) AS sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manufact_id = 128
  AND d_moy = 11
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, brand_id
LIMIT 100;
