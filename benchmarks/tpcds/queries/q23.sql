with frequent_ss_items as (
  select substr(i_item_desc, 1, 30) itemdesc, i_item_sk item_sk,
         d_date solddate, count(*) cnt
  from store_sales, date_dim, item
  where ss_sold_date_sk = d_date_sk
    and ss_item_sk = i_item_sk
    and d_year in (1999, 2000, 2001, 2002)
  group by substr(i_item_desc, 1, 30), i_item_sk, d_date
  having count(*) > 4),
max_store_sales as (
  select max(csales) tpcds_cmax
  from (select c_customer_sk, sum(ss_quantity * ss_sales_price) csales
        from store_sales, customer, date_dim
        where ss_customer_sk = c_customer_sk
          and ss_sold_date_sk = d_date_sk
          and d_year in (1999, 2000, 2001, 2002)
        group by c_customer_sk) x),
best_ss_customer as (
  select c_customer_sk, sum(ss_quantity * ss_sales_price) ssales
  from store_sales, customer
  where ss_customer_sk = c_customer_sk
  group by c_customer_sk
  having sum(ss_quantity * ss_sales_price) >
         0.5 * (select tpcds_cmax from max_store_sales))
select sum(sales)
from (select cs_quantity * cs_list_price sales
      from catalog_sales, date_dim
      where d_year = 2000 and d_moy = 2
        and cs_sold_date_sk = d_date_sk
        and cs_item_sk in (select item_sk from frequent_ss_items)
        and cs_bill_customer_sk in (select c_customer_sk from best_ss_customer)
      union all
      select ws_quantity * ws_list_price sales
      from web_sales, date_dim
      where d_year = 2000 and d_moy = 2
        and ws_sold_date_sk = d_date_sk
        and ws_item_sk in (select item_sk from frequent_ss_items)
        and ws_bill_customer_sk in (select c_customer_sk from best_ss_customer)) y
limit 100
