with ss_ as (
  select s_store_sk, sum(ss_ext_sales_price) as sales,
         sum(ss_net_profit) as profit
  from store_sales, date_dim, store
  where ss_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-22'
    and ss_store_sk = s_store_sk
  group by s_store_sk),
sr_ as (
  select s_store_sk, sum(sr_return_amt) as returns_,
         sum(sr_net_loss) as profit_loss
  from store_returns, date_dim, store
  where sr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-22'
    and sr_store_sk = s_store_sk
  group by s_store_sk),
cs_ as (
  select cs_call_center_sk, sum(cs_ext_sales_price) as sales,
         sum(cs_net_profit) as profit
  from catalog_sales, date_dim
  where cs_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-22'
  group by cs_call_center_sk),
cr_ as (
  select sum(cr_return_amt) as returns_, sum(cr_net_loss) as profit_loss
  from catalog_returns, date_dim
  where cr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-22'),
ws_ as (
  select wp_web_page_sk, sum(ws_ext_sales_price) as sales,
         sum(ws_net_profit) as profit
  from web_sales, date_dim, web_page
  where ws_sold_date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-22'
    and ws_web_page_sk = wp_web_page_sk
  group by wp_web_page_sk),
wr_ as (
  select wp_web_page_sk, sum(wr_return_amt) as returns_,
         sum(wr_net_loss) as profit_loss
  from web_returns, date_dim, web_page
  where wr_returned_date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-22'
    and wr_web_page_sk = wp_web_page_sk
  group by wp_web_page_sk)
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from (select 'store channel' as channel, ss_.s_store_sk as id, sales,
             coalesce(returns_, 0) as returns_,
             profit - coalesce(profit_loss, 0) as profit
      from ss_ left join sr_ on ss_.s_store_sk = sr_.s_store_sk
      union all
      select 'catalog channel' as channel, cs_call_center_sk as id, sales,
             returns_, profit - profit_loss as profit
      from cs_, cr_
      union all
      select 'web channel' as channel, ws_.wp_web_page_sk as id, sales,
             coalesce(returns_, 0) as returns_,
             profit - coalesce(profit_loss, 0) as profit
      from ws_ left join wr_ on ws_.wp_web_page_sk = wr_.wp_web_page_sk) x
group by rollup(channel, id)
order by channel, id
limit 100
