SELECT d_year, i_brand_id AS brand_id, i_brand AS brand,
       sum(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, ext_price DESC, brand_id
LIMIT 100;
