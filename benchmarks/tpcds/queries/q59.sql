with wss as (
  select d_week_seq, ss_store_sk,
         sum(case when d_day_name = 'Sunday' then ss_sales_price else null end) sun_sales,
         sum(case when d_day_name = 'Monday' then ss_sales_price else null end) mon_sales,
         sum(case when d_day_name = 'Tuesday' then ss_sales_price else null end) tue_sales,
         sum(case when d_day_name = 'Wednesday' then ss_sales_price else null end) wed_sales,
         sum(case when d_day_name = 'Thursday' then ss_sales_price else null end) thu_sales,
         sum(case when d_day_name = 'Friday' then ss_sales_price else null end) fri_sales,
         sum(case when d_day_name = 'Saturday' then ss_sales_price else null end) sat_sales
  from store_sales, date_dim
  where d_date_sk = ss_sold_date_sk
  group by d_week_seq, ss_store_sk)
select y.s_store_name1, y.s_store_id1, y.d_week_seq1,
       y.sun_sales1 / x.sun_sales2 r_sun,
       y.mon_sales1 / x.mon_sales2 r_mon,
       y.tue_sales1 / x.tue_sales2 r_tue,
       y.wed_sales1 / x.wed_sales2 r_wed,
       y.thu_sales1 / x.thu_sales2 r_thu,
       y.fri_sales1 / x.fri_sales2 r_fri,
       y.sat_sales1 / x.sat_sales2 r_sat
from (select s_store_name s_store_name1, wss.d_week_seq d_week_seq1,
             s_store_id s_store_id1, sun_sales sun_sales1, mon_sales mon_sales1,
             tue_sales tue_sales1, wed_sales wed_sales1, thu_sales thu_sales1,
             fri_sales fri_sales1, sat_sales sat_sales1
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq
        and ss_store_sk = s_store_sk
        and d_month_seq between 1188 and 1188 + 11) y,
     (select s_store_name s_store_name2, wss.d_week_seq d_week_seq2,
             s_store_id s_store_id2, sun_sales sun_sales2, mon_sales mon_sales2,
             tue_sales tue_sales2, wed_sales wed_sales2, thu_sales thu_sales2,
             fri_sales fri_sales2, sat_sales sat_sales2
      from wss, store, date_dim d
      where d.d_week_seq = wss.d_week_seq
        and ss_store_sk = s_store_sk
        and d_month_seq between 1188 + 12 and 1188 + 23) x
where y.s_store_id1 = x.s_store_id2
  and y.d_week_seq1 = x.d_week_seq2 - 52
order by y.s_store_name1, y.s_store_id1, y.d_week_seq1
limit 100
