SELECT c_last_name, c_first_name, ca_city, bought_city, ss_ticket_number,
       extended_price, extended_tax, list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ca_city AS bought_city,
             sum(ss_ext_sales_price) AS extended_price,
             sum(ss_ext_list_price) AS list_price,
             sum(ss_ext_tax) AS extended_tax
      FROM store_sales, date_dim, store, household_demographics, customer_address
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND ss_addr_sk = ca_address_sk
        AND d_dom BETWEEN 1 AND 2
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_year IN (1999, 2000, 2001)
        AND s_city IN ('Midway', 'Fairview')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address
WHERE dn.ss_customer_sk = c_customer_sk
  AND c_current_addr_sk = customer_address.ca_address_sk
  AND customer_address.ca_city <> dn.bought_city
ORDER BY c_last_name, ss_ticket_number
LIMIT 100;
