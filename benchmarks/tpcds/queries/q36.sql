SELECT sum(ss_net_profit) / sum(ss_ext_sales_price) AS gross_margin,
       i_category, i_class,
       grouping(i_category) + grouping(i_class) AS lochierarchy,
       rank() OVER (PARTITION BY grouping(i_category) + grouping(i_class)
                    ORDER BY sum(ss_net_profit) / sum(ss_ext_sales_price) ASC) AS rank_within_parent
FROM store_sales, date_dim, item, store
WHERE d_year = 2001
  AND d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk
  AND s_store_sk = ss_store_sk
  AND s_state IN ('TN', 'TX', 'SD', 'IN', 'GA', 'OH', 'MI', 'MT')
GROUP BY ROLLUP(i_category, i_class)
ORDER BY lochierarchy DESC, i_category, i_class
LIMIT 100;
