SELECT d_year, i_category_id, i_category, sum(ss_ext_sales_price) AS total
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk
  AND ss_item_sk = i_item_sk
  AND i_manager_id = 1
  AND d_moy = 11
  AND d_year = 2000
GROUP BY d_year, i_category_id, i_category
ORDER BY total DESC, d_year, i_category_id, i_category
LIMIT 100;
