select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, store_sales
where i_current_price between 10 and 150
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2002-05-30' and date '2002-07-30'
  and i_manufact_id in (43, 12, 72, 66)
  and inv_quantity_on_hand between 100 and 500
  and ss_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
