select s_store_name, sum(ss_net_profit)
from store_sales, date_dim, store,
     (select ca_zip
      from (select substr(ca_zip, 1, 5) ca_zip
            from customer_address
            where substr(ca_zip, 1, 5) in
                  ('24000', '24050', '24100', '24150', '24200', '24250',
                   '24300', '24350', '24400', '24450', '24500', '24550',
                   '24010', '24060', '24110', '24160', '24210', '24260',
                   '24310', '24360', '24410', '24460', '24510', '24560')
            intersect
            select ca_zip
            from (select substr(ca_zip, 1, 5) ca_zip, count(*) cnt
                  from customer_address, customer
                  where ca_address_sk = c_current_addr_sk
                    and c_preferred_cust_flag = 'Y'
                  group by ca_zip
                  having count(*) > 10) a1) a2) v1
where ss_store_sk = s_store_sk
  and ss_sold_date_sk = d_date_sk
  and d_qoy = 2
  and d_year = 1998
  and substr(s_zip, 1, 2) = substr(v1.ca_zip, 1, 2)
group by s_store_name
order by s_store_name
limit 100
