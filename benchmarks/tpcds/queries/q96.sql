SELECT count(*) AS cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk
  AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk
  AND t_hour = 20
  AND t_minute >= 30
  AND hd_dep_count = 7
  AND s_store_name = 'store 1'
ORDER BY cnt
LIMIT 100;
