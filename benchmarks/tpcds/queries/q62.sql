select substr(w_warehouse_name, 1, 20) wname, sm_type, web_name,
       sum(case when ws_ship_date_sk - ws_sold_date_sk <= 30 then 1 else 0 end)
         as d30,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 30
                 and ws_ship_date_sk - ws_sold_date_sk <= 60 then 1 else 0 end)
         as d31_60,
       sum(case when ws_ship_date_sk - ws_sold_date_sk > 60 then 1 else 0 end)
         as d_gt_60
from web_sales, warehouse, ship_mode, web_site, date_dim
where d_year = 2001
  and ws_ship_date_sk = d_date_sk
  and ws_warehouse_sk = w_warehouse_sk
  and ws_ship_mode_sk = sm_ship_mode_sk
  and ws_web_site_sk = web_site_sk
group by substr(w_warehouse_name, 1, 20), sm_type, web_name
order by wname, sm_type, web_name
limit 100
