select *
from (select count(*) h8_30_to_9 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
        and ss_store_sk = s_store_sk and t_hour = 8 and t_minute >= 30
        and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
          or (hd_dep_count = 2 and hd_vehicle_count <= 4)
          or (hd_dep_count = 0 and hd_vehicle_count <= 2))
        and s_store_name = 'store 1') s1,
     (select count(*) h9_to_9_30 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
        and ss_store_sk = s_store_sk and t_hour = 9 and t_minute < 30
        and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
          or (hd_dep_count = 2 and hd_vehicle_count <= 4)
          or (hd_dep_count = 0 and hd_vehicle_count <= 2))
        and s_store_name = 'store 1') s2,
     (select count(*) h9_30_to_10 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
        and ss_store_sk = s_store_sk and t_hour = 9 and t_minute >= 30
        and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
          or (hd_dep_count = 2 and hd_vehicle_count <= 4)
          or (hd_dep_count = 0 and hd_vehicle_count <= 2))
        and s_store_name = 'store 1') s3,
     (select count(*) h10_to_10_30 from store_sales, household_demographics, time_dim, store
      where ss_sold_time_sk = t_time_sk and ss_hdemo_sk = hd_demo_sk
        and ss_store_sk = s_store_sk and t_hour = 10 and t_minute < 30
        and ((hd_dep_count = 4 and hd_vehicle_count <= 6)
          or (hd_dep_count = 2 and hd_vehicle_count <= 4)
          or (hd_dep_count = 0 and hd_vehicle_count <= 2))
        and s_store_name = 'store 1') s4
