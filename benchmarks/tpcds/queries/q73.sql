SELECT c_last_name, c_first_name, c_customer_sk AS c_salutation, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) AS cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND d_dom BETWEEN 1 AND 2
        AND (hd_buy_potential = '>10000' OR hd_buy_potential = 'Unknown')
        AND hd_vehicle_count > 0
        AND d_year IN (1999, 2000, 2001)
        AND s_county IN ('Williamson County', 'Walker County')
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE dj.ss_customer_sk = c_customer_sk
  AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name
LIMIT 100;
