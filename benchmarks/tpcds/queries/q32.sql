select sum(cs_ext_discount_amt) as excess_discount_amount
from catalog_sales, item, date_dim
where i_manufact_id = 77
  and i_item_sk = cs_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = cs_sold_date_sk
  and cs_ext_discount_amt > (
      select 1.3 * avg(cs_ext_discount_amt)
      from catalog_sales, date_dim
      where cs_item_sk = i_item_sk
        and d_date between date '2000-01-27' and date '2000-04-26'
        and d_date_sk = cs_sold_date_sk)
