select w_warehouse_name, i_item_id,
       sum(case when d_date < date '2000-03-11' then inv_quantity_on_hand
                else 0 end) as inv_before,
       sum(case when d_date >= date '2000-03-11' then inv_quantity_on_hand
                else 0 end) as inv_after
from inventory, warehouse, item, date_dim
where i_current_price between 0.99 and 29.49
  and i_item_sk = inv_item_sk
  and inv_warehouse_sk = w_warehouse_sk
  and inv_date_sk = d_date_sk
  and d_date between date '2000-02-10' and date '2000-04-10'
group by w_warehouse_name, i_item_id
having (case when sum(case when d_date < date '2000-03-11' then inv_quantity_on_hand else 0 end) > 0
             then sum(case when d_date >= date '2000-03-11' then inv_quantity_on_hand else 0 end) * 1.0
                  / sum(case when d_date < date '2000-03-11' then inv_quantity_on_hand else 0 end)
             else null end) between 2.0 / 3.0 and 3.0 / 2.0
order by w_warehouse_name, i_item_id
limit 100
