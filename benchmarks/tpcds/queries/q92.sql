select sum(ws_ext_discount_amt) as excess_discount_amount
from web_sales, item, date_dim
where i_manufact_id = 53
  and i_item_sk = ws_item_sk
  and d_date between date '2000-01-27' and date '2000-04-26'
  and d_date_sk = ws_sold_date_sk
  and ws_ext_discount_amt > (
      select 1.3 * avg(ws_ext_discount_amt)
      from web_sales, date_dim
      where ws_item_sk = i_item_sk
        and d_date between date '2000-01-27' and date '2000-04-26'
        and d_date_sk = ws_sold_date_sk)
