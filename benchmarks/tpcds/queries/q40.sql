select w_state, i_item_id,
       sum(case when d_date < date '2000-03-11' then cs_sales_price - 0.0 else 0.0 end)
         as sales_before,
       sum(case when d_date >= date '2000-03-11' then cs_sales_price - 0.0 else 0.0 end)
         as sales_after
from catalog_sales, warehouse, item, date_dim
where i_current_price between 0.99 and 110.99
  and i_item_sk = cs_item_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_sold_date_sk = d_date_sk
  and d_date between date '2000-02-10' and date '2000-04-10'
group by w_state, i_item_id
order by w_state, i_item_id
limit 100
