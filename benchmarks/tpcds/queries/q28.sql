select b1_lp, b1_cnt, b1_cntd, b2_lp, b2_cnt, b2_cntd, b3_lp, b3_cnt,
       b3_cntd, b4_lp, b4_cnt, b4_cntd, b5_lp, b5_cnt, b5_cntd, b6_lp,
       b6_cnt, b6_cntd
from (select avg(ss_list_price) b1_lp, count(ss_list_price) b1_cnt,
             count(distinct ss_list_price) b1_cntd
      from store_sales
      where ss_quantity between 0 and 5
        and (ss_list_price between 8 and 18
             or ss_coupon_amt between 459 and 1459
             or ss_wholesale_cost between 57 and 77)) b1,
     (select avg(ss_list_price) b2_lp, count(ss_list_price) b2_cnt,
             count(distinct ss_list_price) b2_cntd
      from store_sales
      where ss_quantity between 6 and 10
        and (ss_list_price between 90 and 100
             or ss_coupon_amt between 2323 and 3323
             or ss_wholesale_cost between 31 and 51)) b2,
     (select avg(ss_list_price) b3_lp, count(ss_list_price) b3_cnt,
             count(distinct ss_list_price) b3_cntd
      from store_sales
      where ss_quantity between 11 and 15
        and (ss_list_price between 142 and 152
             or ss_coupon_amt between 12214 and 13214
             or ss_wholesale_cost between 79 and 99)) b3,
     (select avg(ss_list_price) b4_lp, count(ss_list_price) b4_cnt,
             count(distinct ss_list_price) b4_cntd
      from store_sales
      where ss_quantity between 16 and 20
        and (ss_list_price between 135 and 145
             or ss_coupon_amt between 6071 and 7071
             or ss_wholesale_cost between 38 and 58)) b4,
     (select avg(ss_list_price) b5_lp, count(ss_list_price) b5_cnt,
             count(distinct ss_list_price) b5_cntd
      from store_sales
      where ss_quantity between 21 and 25
        and (ss_list_price between 122 and 132
             or ss_coupon_amt between 836 and 1836
             or ss_wholesale_cost between 17 and 37)) b5,
     (select avg(ss_list_price) b6_lp, count(ss_list_price) b6_cnt,
             count(distinct ss_list_price) b6_cntd
      from store_sales
      where ss_quantity between 26 and 30
        and (ss_list_price between 154 and 164
             or ss_coupon_amt between 7326 and 8326
             or ss_wholesale_cost between 7 and 27)) b6
limit 100
