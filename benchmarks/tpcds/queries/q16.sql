select count(distinct cs_order_number) as order_count,
       sum(cs_ext_ship_cost) as total_shipping_cost,
       sum(cs_net_profit) as total_net_profit
from catalog_sales cs1, date_dim, customer_address, call_center
where d_date between date '2000-02-01' and date '2000-04-02'
  and cs1.cs_ship_date_sk = d_date_sk
  and cs1.cs_ship_addr_sk = ca_address_sk
  and ca_state = 'GA'
  and cs1.cs_call_center_sk = cc_call_center_sk
  and cc_county in ('Williamson County', 'Walker County', 'Ziebach County')
  and exists (select 1 from catalog_sales cs2
              where cs1.cs_order_number = cs2.cs_order_number
                and cs1.cs_warehouse_sk <> cs2.cs_warehouse_sk)
  and not exists (select 1 from catalog_returns cr1
                  where cs1.cs_order_number = cr1.cr_order_number)
order by order_count
limit 100
