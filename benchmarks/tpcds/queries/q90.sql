select cast(amc as double) / cast(pmc as double) am_pm_ratio
from (select count(*) amc from web_sales, household_demographics, time_dim, web_page
      where ws_sold_time_sk = t_time_sk and ws_ship_hdemo_sk = hd_demo_sk
        and ws_web_page_sk = wp_web_page_sk
        and t_hour between 8 and 9
        and hd_dep_count = 6 and wp_char_count between 5000 and 5200) at1,
     (select count(*) pmc from web_sales, household_demographics, time_dim, web_page
      where ws_sold_time_sk = t_time_sk and ws_ship_hdemo_sk = hd_demo_sk
        and ws_web_page_sk = wp_web_page_sk
        and t_hour between 19 and 20
        and hd_dep_count = 6 and wp_char_count between 5000 and 5200) pt
order by am_pm_ratio
limit 100
