select s_store_name, s_county,
       sum(case when sr_returned_date_sk - ss_sold_date_sk <= 30 then 1 else 0 end)
         as d30,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 30
                 and sr_returned_date_sk - ss_sold_date_sk <= 60 then 1 else 0 end)
         as d31_60,
       sum(case when sr_returned_date_sk - ss_sold_date_sk > 60 then 1 else 0 end)
         as d_gt_60
from store_sales, store_returns, store, date_dim d2
where ss_ticket_number = sr_ticket_number and ss_item_sk = sr_item_sk
  and ss_customer_sk = sr_customer_sk
  and sr_returned_date_sk = d2.d_date_sk
  and d2.d_year = 2001 and d2.d_moy = 8
  and ss_store_sk = s_store_sk
group by s_store_name, s_county
order by s_store_name, s_county
limit 100
