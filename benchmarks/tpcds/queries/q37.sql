select i_item_id, i_item_desc, i_current_price
from item, inventory, date_dim, catalog_sales
where i_current_price between 10 and 150
  and inv_item_sk = i_item_sk
  and d_date_sk = inv_date_sk
  and d_date between date '2000-02-01' and date '2000-04-01'
  and i_manufact_id in (67, 96, 91, 84)
  and inv_quantity_on_hand between 100 and 500
  and cs_item_sk = i_item_sk
group by i_item_id, i_item_desc, i_current_price
order by i_item_id
limit 100
