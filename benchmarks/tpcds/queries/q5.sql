with ssr as (
  select s_store_id, sum(sales_price) as sales, sum(profit) as profit,
         sum(return_amt) as returns_, sum(net_loss) as profit_loss
  from (select ss_store_sk as store_sk, ss_sold_date_sk as date_sk,
               ss_ext_sales_price as sales_price, ss_net_profit as profit,
               cast(0 as float) as return_amt, cast(0 as float) as net_loss
        from store_sales
        union all
        select sr_store_sk as store_sk, sr_returned_date_sk as date_sk,
               cast(0 as float) as sales_price, cast(0 as float) as profit,
               sr_return_amt as return_amt, sr_net_loss as net_loss
        from store_returns) salesreturns, date_dim, store
  where date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-06'
    and store_sk = s_store_sk
  group by s_store_id),
csr as (
  select cc_call_center_id, sum(sales_price) as sales, sum(profit) as profit,
         sum(return_amt) as returns_, sum(net_loss) as profit_loss
  from (select cs_call_center_sk as center_sk, cs_sold_date_sk as date_sk,
               cs_ext_sales_price as sales_price, cs_net_profit as profit,
               cast(0 as float) as return_amt, cast(0 as float) as net_loss
        from catalog_sales
        union all
        select cr_call_center_sk as center_sk, cr_returned_date_sk as date_sk,
               cast(0 as float) as sales_price, cast(0 as float) as profit,
               cr_return_amt as return_amt, cr_net_loss as net_loss
        from catalog_returns) salesreturns, date_dim, call_center
  where date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-06'
    and center_sk = cc_call_center_sk
  group by cc_call_center_id),
wsr as (
  select web_site_id, sum(sales_price) as sales, sum(profit) as profit,
         sum(return_amt) as returns_, sum(net_loss) as profit_loss
  from (select ws_web_site_sk as wsr_web_site_sk, ws_sold_date_sk as date_sk,
               ws_ext_sales_price as sales_price, ws_net_profit as profit,
               cast(0 as float) as return_amt, cast(0 as float) as net_loss
        from web_sales
        union all
        select ws_web_site_sk as wsr_web_site_sk, wr_returned_date_sk as date_sk,
               cast(0 as float) as sales_price, cast(0 as float) as profit,
               wr_return_amt as return_amt, wr_net_loss as net_loss
        from web_returns left outer join web_sales
          on (wr_item_sk = ws_item_sk
              and wr_order_number = ws_order_number)) salesreturns,
       date_dim, web_site
  where date_sk = d_date_sk
    and d_date between date '2000-08-23' and date '2000-09-06'
    and wsr_web_site_sk = web_site_sk
  group by web_site_id)
select channel, id, sum(sales) as sales, sum(returns_) as returns_,
       sum(profit) as profit
from (select 'store channel' as channel, s_store_id as id, sales, returns_,
             profit - profit_loss as profit
      from ssr
      union all
      select 'catalog channel' as channel, cc_call_center_id as id, sales,
             returns_, profit - profit_loss as profit
      from csr
      union all
      select 'web channel' as channel, web_site_id as id, sales, returns_,
             profit - profit_loss as profit
      from wsr) x
group by rollup(channel, id)
order by channel, id
limit 100
