select distinct i_product_name
from item i1
where i_manufact_id between 70 and 70 + 40
  and (select count(*) as item_cnt
       from item
       where i_manufact = i1.i_manufact
         and ((i_category = 'Women'
               and (i_color = 'papaya' or i_color = 'frosted')
               and (i_units = 'Ounce' or i_units = 'Ton')
               and (i_size = 'medium' or i_size = 'extra large'))
              or (i_category = 'Women'
                  and (i_color = 'chiffon' or i_color = 'lace')
                  and (i_units = 'Pound' or i_units = 'Dram')
                  and (i_size = 'economy' or i_size = 'small'))
              or (i_category = 'Men'
                  and (i_color = 'orchid' or i_color = 'peach')
                  and (i_units = 'Bundle' or i_units = 'Gross')
                  and (i_size = 'N/A' or i_size = 'large'))
              or (i_category = 'Men'
                  and (i_color = 'smoke' or i_color = 'dim')
                  and (i_units = 'Each' or i_units = 'Oz')
                  and (i_size = 'medium' or i_size = 'petite')))) > 0
order by i_product_name
limit 100
