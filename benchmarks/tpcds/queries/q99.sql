select substr(w_warehouse_name, 1, 20) wname, sm_type, cc_name,
       sum(case when cs_ship_date_sk - cs_sold_date_sk <= 30 then 1 else 0 end)
         as d30,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 30
                 and cs_ship_date_sk - cs_sold_date_sk <= 60 then 1 else 0 end)
         as d31_60,
       sum(case when cs_ship_date_sk - cs_sold_date_sk > 60 then 1 else 0 end)
         as d_gt_60
from catalog_sales, warehouse, ship_mode, call_center, date_dim
where d_year = 2001
  and cs_ship_date_sk = d_date_sk
  and cs_warehouse_sk = w_warehouse_sk
  and cs_ship_mode_sk = sm_ship_mode_sk
  and cs_call_center_sk = cc_call_center_sk
group by substr(w_warehouse_name, 1, 20), sm_type, cc_name
order by wname, sm_type, cc_name
limit 100
