"""TPC-DS benchmark runner (rebuild of benchmarks/src/bin/tpcds.rs).

Query subset: the retail-sales queries answerable from the generated core
tables (see ballista_tpu/testing/tpcdsgen.py). Modes mirror tpch.py:

  python benchmarks/tpcds.py data --scale 1 --out /tmp/tpcds_sf1
  python benchmarks/tpcds.py run --data /tmp/tpcds_sf1 [--query 3] \
      [--engine cpu|tpu] [--mode local|standalone] [--iterations 1] [--verify]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

QUERIES = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15, 16, 17, 18, 19, 20, 21, 22, 23, 24, 25, 26, 27, 28, 29, 30, 31, 32, 33, 34, 35, 36, 37, 38, 39, 40, 41, 42, 43, 44, 45, 46, 47, 48, 49, 50, 51, 52, 53, 54, 55, 56, 57, 58, 59, 60, 61, 62, 63, 64, 65, 66, 67, 68, 69, 70, 71, 72, 73, 74, 75, 76, 77, 78, 79, 80, 81, 82, 83, 84, 85, 86, 87, 88, 89, 90, 91, 92, 93, 94, 95, 96, 97, 98, 99]


def q_path(n: int) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "tpcds", "queries", f"q{n}.sql")


def cmd_data(args) -> None:
    from ballista_tpu.testing.tpcdsgen import generate_tpcds

    t0 = time.time()
    generate_tpcds(args.out, scale=args.scale, files_per_table=args.files)
    print(f"generated tpcds scale={args.scale} at {args.out} in {time.time() - t0:.1f}s")


def cmd_run(args) -> None:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE
    from ballista_tpu.testing.tpcdsgen import register_tpcds

    cfg = BallistaConfig({EXECUTOR_ENGINE: args.engine})
    if args.mode == "standalone":
        ctx = SessionContext.standalone(cfg)
    else:
        ctx = SessionContext(cfg)
    register_tpcds(ctx, args.data)

    ref_tables = None
    if args.verify:
        from ballista_tpu.testing.tpcds_reference import load_tables

        ref_tables = load_tables(args.data)

    queries = [args.query] if args.query else QUERIES
    results = []
    for q in queries:
        sql = open(q_path(q)).read()
        times = []
        out = None
        for _ in range(args.iterations):
            t0 = time.time()
            out = ctx.sql(sql).collect()
            times.append(time.time() - t0)
        entry = {"query": f"q{q}", "time_s": round(min(times), 3), "rows": out.num_rows}
        if args.verify:
            from ballista_tpu.testing.tpcds_reference import compare_results, run_reference

            problems = compare_results(out, run_reference(q, ref_tables), q)
            entry["verified"] = not problems
            if problems:
                entry["problems"] = problems
        results.append(entry)
        print(entry, file=sys.stderr)
    print(json.dumps(results))


def main() -> None:
    ap = argparse.ArgumentParser(description="TPC-DS benchmark")
    sub = ap.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("data")
    d.add_argument("--scale", type=float, default=1.0)
    d.add_argument("--out", required=True)
    d.add_argument("--files", type=int, default=2)
    d.set_defaults(fn=cmd_data)
    r = sub.add_parser("run")
    r.add_argument("--data", required=True)
    r.add_argument("--query", type=int, default=None)
    r.add_argument("--engine", choices=("cpu", "tpu"), default="cpu")
    r.add_argument("--mode", choices=("local", "standalone"), default="local")
    r.add_argument("--iterations", type=int, default=1)
    r.add_argument("--verify", action="store_true")
    r.set_defaults(fn=cmd_run)
    args = ap.parse_args()
    args.fn(args)


if __name__ == "__main__":
    main()
