"""Standalone shuffle micro-benchmark.

Rebuild of benchmarks/src/bin/shuffle_bench.rs + benches/sort_shuffle.rs:
profiles the shuffle writer in isolation — hash layout vs sort-consolidated
layout, native C++ row router vs numpy fallback — and the reader's local
and raw-block Flight paths, without a scheduler in the way.

  python benchmarks/shuffle_bench.py [--rows 2000000] [--partitions 16]
      [--layout sort|hash|both] [--read local|flight|none] [--json]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa


def make_batches(rows: int, batch_size: int = 64 * 1024) -> list[pa.RecordBatch]:
    rng = np.random.default_rng(7)
    out = []
    for off in range(0, rows, batch_size):
        n = min(batch_size, rows - off)
        out.append(pa.record_batch({
            "k": pa.array(rng.integers(0, 1 << 30, n)),
            "v": pa.array(rng.integers(0, 1000, n)),
            "price": pa.array(np.round(rng.uniform(0, 1000, n), 2)),
            "s": pa.array(rng.choice(["alpha", "beta", "gamma", "delta"], n)),
        }))
    return out


def run_write(batches, work_dir: str, partitions: int, sort_shuffle: bool, ctx,
              maps: int = 1):
    from ballista_tpu.plan.expressions import Column
    from ballista_tpu.plan.physical import MemoryScanExec
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle.writer import ShuffleWriterExec

    schema = DFSchema.from_arrow(batches[0].schema)
    scan = MemoryScanExec(schema, batches, partitions=maps)
    writer = ShuffleWriterExec(
        scan, "bench-job", 1, partitions, [Column("k")], sort_shuffle=sort_shuffle
    )
    t0 = time.time()
    metas = []
    for m in range(maps):
        for b in writer.execute(m, ctx):
            metas.append(b)
    dt = time.time() - t0
    total_bytes = sum(
        os.path.getsize(os.path.join(root, f))
        for root, _, files in os.walk(work_dir) for f in files
    )
    return dt, total_bytes


def run_read(work_dir: str, partitions: int, layout: str, mode: str, ctx, rows: int):
    from ballista_tpu.shuffle import paths
    from ballista_tpu.shuffle.reader import fetch_partition
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    locs = []
    stage_dir = os.path.join(work_dir, "bench-job", "1")
    for root, _, files in os.walk(stage_dir):
        for f in files:
            if f.endswith(".idx"):
                continue
            path = os.path.join(root, f)
            if layout == "sort":
                # consolidated file: one location per output partition
                for p in range(partitions):
                    locs.append(PartitionLocation(
                        map_partition=0, job_id="bench-job", stage_id=1,
                        output_partition=p, executor_id="e", host="127.0.0.1",
                        flight_port=0, path=path, layout=layout,
                        stats=PartitionStats(0, 0, 0),
                    ))
            else:
                # hash layout: the directory name IS the output partition
                p = int(os.path.basename(root))
                locs.append(PartitionLocation(
                    map_partition=0, job_id="bench-job", stage_id=1,
                    output_partition=p, executor_id="e", host="127.0.0.1",
                    flight_port=0, path=path, layout=layout,
                    stats=PartitionStats(0, 0, 0),
                ))
    t0 = time.time()
    got = 0
    server = None
    try:
        if mode == "flight":
            from ballista_tpu.flight.server import start_flight_server

            server, port = start_flight_server(work_dir, "127.0.0.1", 0)
            locs = [
                PartitionLocation(**{**l.__dict__, "flight_port": port, "path": l.path})
                for l in locs
            ]
            for l in locs:
                for b in fetch_partition(l, ctx, force_remote=True):
                    got += b.num_rows
        else:
            for l in locs:
                for b in fetch_partition(l, ctx):
                    got += b.num_rows
    finally:
        if server is not None:
            server.shutdown()
    dt = time.time() - t0
    assert got == rows, f"read {got} rows, expected {rows}"
    return dt


def run_reader_exec(work_dir: str, partitions: int, layout: str, ctx, rows: int,
                    coalesce: bool = True):
    """The REAL reduce path: ShuffleReaderExec over a Flight server, all of
    a partition's upstream locations fetched concurrently under the
    governor. Reports seconds plus data-plane accounting — server-side RPC
    counts by kind, bytes moved by provenance, and time-to-first-batch — so
    a coalesce-on vs coalesce-off pair shows the RPC collapse directly
    (shuffle_reader.rs:762-875)."""
    from ballista_tpu.config import SHUFFLE_FETCH_COALESCE
    from ballista_tpu.flight.server import start_flight_server
    from ballista_tpu.plan.schema import DFSchema
    from ballista_tpu.shuffle.reader import ShuffleReaderExec
    from ballista_tpu.shuffle.types import PartitionLocation, PartitionStats

    stage_dir = os.path.join(work_dir, "bench-job", "1")
    per_part: dict[int, list] = {p: [] for p in range(partitions)}
    for root, _, files in os.walk(stage_dir):
        for f in files:
            if f.endswith(".idx"):
                continue
            path = os.path.join(root, f)
            if layout == "sort":
                for p in range(partitions):
                    per_part[p].append((path, p))
            else:
                p = int(os.path.basename(root))
                per_part[p].append((path, p))
    server, port = start_flight_server(work_dir, "127.0.0.1", 0)
    try:
        schema = DFSchema.from_arrow(
            pa.schema([("k", pa.int64()), ("v", pa.int64()),
                       ("price", pa.float64()), ("s", pa.string())]), "t")
        locs = [
            [
                PartitionLocation(
                    map_partition=m, job_id="bench-job", stage_id=1,
                    output_partition=p, executor_id="e", host="127.0.0.1",
                    flight_port=port, path=path, layout=layout,
                    stats=PartitionStats(0, 0, 0),
                )
                for m, (path, _p) in enumerate(per_part[p])
            ]
            for p in range(partitions)
        ]
        rd = ShuffleReaderExec(schema, locs)
        rctx = _force_remote(ctx, {SHUFFLE_FETCH_COALESCE: coalesce})
        stats0 = dict(server.stats)
        acc = {"fetch_rpcs": 0, "bytes_fetched_remote": 0, "bytes_read_local": 0}
        ttfb_ns = None
        t0 = time.time()
        got = 0
        for p in range(partitions):
            for b in rd.execute(p, rctx):
                got += b.num_rows
            extra = rd.metrics.extra
            for k in acc:
                acc[k] += int(extra.get(k, 0))
            if ttfb_ns is None and "time_to_first_batch_ns" in extra:
                ttfb_ns = extra["time_to_first_batch_ns"]
        dt = time.time() - t0
        rpc_delta = {k: server.stats[k] - stats0[k]
                     for k in ("do_get", "block_rpc", "coalesced_rpc")}
    finally:
        server.shutdown()
    assert got == rows, f"reader exec read {got} rows, expected {rows}"
    return {
        "seconds": dt,
        "fetch_rpcs": acc["fetch_rpcs"],
        "server_rpcs": rpc_delta,
        "bytes_remote": acc["bytes_fetched_remote"],
        "bytes_local": acc["bytes_read_local"],
        "time_to_first_batch_ms": round((ttfb_ns or 0) / 1e6, 3),
    }


def _force_remote(ctx, extra: dict | None = None):
    from ballista_tpu.config import SHUFFLE_READER_FORCE_REMOTE, BallistaConfig
    from ballista_tpu.plan.physical import TaskContext

    cfg = BallistaConfig.from_key_value_pairs(ctx.config.to_key_value_pairs())
    cfg.set(SHUFFLE_READER_FORCE_REMOTE, True)
    for k, v in (extra or {}).items():
        cfg.set(k, v)
    return TaskContext(cfg)


def main() -> None:
    ap = argparse.ArgumentParser(description="shuffle writer/reader micro-benchmark")
    ap.add_argument("--rows", type=int, default=2_000_000)
    ap.add_argument("--partitions", type=int, default=16)
    ap.add_argument("--maps", type=int, default=4,
                    help="upstream map tasks; >1 gives coalescing something to merge")
    ap.add_argument("--layout", choices=("sort", "hash", "both"), default="both")
    ap.add_argument("--read", choices=("local", "flight", "reader", "none"), default="local")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    from ballista_tpu.config import BallistaConfig, SORT_SHUFFLE_ENABLED
    from ballista_tpu.plan.physical import TaskContext

    batches = make_batches(args.rows)
    results = []
    layouts = ("sort", "hash") if args.layout == "both" else (args.layout,)
    for layout in layouts:
        work = tempfile.mkdtemp(prefix=f"shuffle-bench-{layout}-")
        cfg = BallistaConfig({SORT_SHUFFLE_ENABLED: layout == "sort"})
        ctx = TaskContext(cfg, work_dir=work)
        wt, nbytes = run_write(batches, work, args.partitions, layout == "sort",
                               ctx, maps=args.maps)
        entry = {
            "layout": layout, "rows": args.rows, "partitions": args.partitions,
            "maps": args.maps,
            "write_s": round(wt, 3),
            "write_rows_per_s": int(args.rows / wt),
            "bytes": nbytes,
            "files": sum(len(fs) for _, _, fs in os.walk(work)),
        }
        if args.read == "reader":
            # before/after pair: same data, coalescing off vs on — the JSON
            # line is the BENCH capture for the RPC-collapse win
            for coalesce in (False, True):
                r = run_reader_exec(work, args.partitions, layout, ctx,
                                    args.rows, coalesce=coalesce)
                tag = "coalesced" if coalesce else "uncoalesced"
                entry[f"read_reader_{tag}_s"] = round(r["seconds"], 3)
                entry[f"read_reader_{tag}_rows_per_s"] = int(args.rows / r["seconds"])
                entry[f"read_reader_{tag}_fetch_rpcs"] = r["fetch_rpcs"]
                entry[f"read_reader_{tag}_server_rpcs"] = r["server_rpcs"]
                entry[f"read_reader_{tag}_bytes_remote"] = r["bytes_remote"]
                entry[f"read_reader_{tag}_bytes_local"] = r["bytes_local"]
                entry[f"read_reader_{tag}_ttfb_ms"] = r["time_to_first_batch_ms"]
        elif args.read != "none":
            rt = run_read(work, args.partitions, layout, args.read, ctx, args.rows)
            entry[f"read_{args.read}_s"] = round(rt, 3)
            entry[f"read_{args.read}_rows_per_s"] = int(args.rows / rt)
        results.append(entry)
        shutil.rmtree(work, ignore_errors=True)

    if args.json:
        print(json.dumps(results))
    else:
        for r in results:
            print(r)


if __name__ == "__main__":
    main()
