"""h2o db-benchmark harness (groupby + join sets).

Rebuild of the reference's benchmarks/db-benchmark scripts: generates the
standard G1 groupby table / J1 join tables, runs the h2o query set through
the engine, and verifies against pandas.

  python benchmarks/h2o.py groupby --rows 1000000 [--engine cpu|tpu] [--verify]
  python benchmarks/h2o.py join    --rows 1000000 [--verify]

q6 (median/sd) and q9 (corr) need aggregates outside the engine's set and
are reported as skipped — the same subset public h2o runs mark for engines
without those aggregates.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import pyarrow as pa

GROUPBY_QUERIES = {
    "q1": "select id1, sum(v1) as v1 from x group by id1",
    "q2": "select id1, id2, sum(v1) as v1 from x group by id1, id2",
    "q3": "select id3, sum(v1) as v1, avg(v3) as v3 from x group by id3",
    "q4": "select id4, avg(v1) as v1, avg(v2) as v2, avg(v3) as v3 from x group by id4",
    "q5": "select id6, sum(v1) as v1, sum(v2) as v2, sum(v3) as v3 from x group by id6",
    "q7": "select id3, max(v1) - min(v2) as range_v1_v2 from x group by id3",
    "q8": (
        "select id6, v3 from ("
        "select id6, v3, row_number() over (partition by id6 order by v3 desc) rn "
        "from x) t where rn <= 2"
    ),
    "q10": (
        "select id1, id2, id3, id4, id5, id6, sum(v3) as v3, count(*) as cnt "
        "from x group by id1, id2, id3, id4, id5, id6"
    ),
}
SKIPPED = {"q6": "median/sd aggregates", "q9": "corr aggregate"}

JOIN_QUERIES = {
    "j1": "select x.id1 as xid1, small.id1, x.v1, small.v2 from x, small where x.id1 = small.id1",
    "j2": "select x.id2 as xid2, medium.id2, x.v1, medium.v2 from x, medium where x.id2 = medium.id2",
    "j3": "select x.id3 as xid3, big.id3, x.v1, big.v2 from x, big where x.id3 = big.id3",
}


def gen_groupby(rows: int, k: int = 100) -> pa.Table:
    rng = np.random.default_rng(42)
    return pa.table({
        "id1": np.char.add("id", rng.integers(1, k + 1, rows).astype(str)),
        "id2": np.char.add("id", rng.integers(1, k + 1, rows).astype(str)),
        "id3": np.char.add("id", rng.integers(1, rows // 10 + 2, rows).astype(str)),
        "id4": rng.integers(1, k + 1, rows),
        "id5": rng.integers(1, k + 1, rows),
        "id6": rng.integers(1, rows // 10 + 2, rows),
        "v1": rng.integers(1, 6, rows),
        "v2": rng.integers(1, 16, rows),
        "v3": np.round(rng.uniform(0, 100, rows), 6),
    })


def gen_join(rows: int) -> dict[str, pa.Table]:
    rng = np.random.default_rng(43)
    x = pa.table({
        "id1": rng.integers(1, rows // 1_000 + 2, rows),
        "id2": rng.integers(1, rows // 100 + 2, rows),
        "id3": rng.integers(1, rows // 10 + 2, rows),
        "v1": np.round(rng.uniform(0, 100, rows), 6),
    })
    small = pa.table({
        "id1": np.arange(1, rows // 1_000 + 2),
        "v2": np.round(rng.uniform(0, 100, rows // 1_000 + 1), 6),
    })
    medium = pa.table({
        "id2": np.arange(1, rows // 100 + 2),
        "v2": np.round(rng.uniform(0, 100, rows // 100 + 1), 6),
    })
    big = pa.table({
        "id3": np.arange(1, rows // 10 + 2),
        "v2": np.round(rng.uniform(0, 100, rows // 10 + 1), 6),
    })
    return {"x": x, "small": small, "medium": medium, "big": big}


def _verify_groupby(name: str, out, x: pa.Table) -> str | None:
    df = x.to_pandas()
    o = out.to_pandas()
    if name == "q1":
        e = df.groupby("id1", as_index=False).agg(v1=("v1", "sum"))
    elif name == "q2":
        e = df.groupby(["id1", "id2"], as_index=False).agg(v1=("v1", "sum"))
    elif name == "q3":
        e = df.groupby("id3", as_index=False).agg(v1=("v1", "sum"), v3=("v3", "mean"))
    elif name == "q4":
        e = df.groupby("id4", as_index=False).agg(v1=("v1", "mean"), v2=("v2", "mean"), v3=("v3", "mean"))
    elif name == "q5":
        e = df.groupby("id6", as_index=False).agg(v1=("v1", "sum"), v2=("v2", "sum"), v3=("v3", "sum"))
    elif name == "q7":
        e = df.groupby("id3", as_index=False).agg(mx=("v1", "max"), mn=("v2", "min"))
        e["range_v1_v2"] = e.mx - e.mn
        e = e[["id3", "range_v1_v2"]]
    elif name == "q8":
        s = df.sort_values("v3", ascending=False).groupby("id6").head(2)
        e = s[["id6", "v3"]]
    elif name == "q10":
        e = df.groupby(["id1", "id2", "id3", "id4", "id5", "id6"], as_index=False).agg(
            v3=("v3", "sum"), cnt=("v3", "size")
        )
    else:
        return None
    if len(o) != len(e):
        return f"{name}: row count {len(o)} != {len(e)}"
    o2 = o.sort_values(list(o.columns)).reset_index(drop=True)
    e2 = e.sort_values(list(e.columns)).reset_index(drop=True)
    for c in e2.columns:
        a, b = o2[c].values, e2[c].values
        if a.dtype.kind == "f" or b.dtype.kind == "f":
            if not np.allclose(a.astype(float), b.astype(float), rtol=1e-9, atol=1e-9):
                return f"{name}: column {c} mismatch"
        elif not (a == b).all():
            return f"{name}: column {c} mismatch"
    return None


def main() -> None:
    ap = argparse.ArgumentParser(description="h2o db-benchmark harness")
    ap.add_argument("mode", choices=("groupby", "join"))
    ap.add_argument("--rows", type=int, default=1_000_000)
    ap.add_argument("--engine", choices=("cpu", "tpu"), default="cpu")
    ap.add_argument("--partitions", type=int, default=8)
    ap.add_argument("--verify", action="store_true")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()

    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig, EXECUTOR_ENGINE

    ctx = SessionContext(BallistaConfig({EXECUTOR_ENGINE: args.engine}))
    results = []
    if args.mode == "groupby":
        x = gen_groupby(args.rows)
        ctx.register_arrow_table("x", x, partitions=args.partitions)
        for name, sql in GROUPBY_QUERIES.items():
            t0 = time.time()
            out = ctx.sql(sql).collect()
            dt = time.time() - t0
            entry = {"query": name, "time_s": round(dt, 3), "out_rows": out.num_rows}
            if args.verify:
                problem = _verify_groupby(name, out, x)
                entry["verified"] = problem is None
                if problem:
                    entry["problem"] = problem
            results.append(entry)
        for name, why in SKIPPED.items():
            results.append({"query": name, "skipped": why})
    else:
        tables = gen_join(args.rows)
        for name, tbl in tables.items():
            ctx.register_arrow_table(name, tbl, partitions=args.partitions if name == "x" else 1)
        xx = tables["x"].to_pandas() if args.verify else None
        for name, sql in JOIN_QUERIES.items():
            t0 = time.time()
            out = ctx.sql(sql).collect()
            dt = time.time() - t0
            entry = {"query": name, "time_s": round(dt, 3), "out_rows": out.num_rows}
            if args.verify:
                other = {"j1": "small", "j2": "medium", "j3": "big"}[name]
                key = {"j1": "id1", "j2": "id2", "j3": "id3"}[name]
                e = xx.merge(tables[other].to_pandas(), on=key)
                entry["verified"] = out.num_rows == len(e)
                if not entry["verified"]:
                    entry["problem"] = f"rows {out.num_rows} != {len(e)}"
            results.append(entry)

    print(json.dumps(results) if args.json else "\n".join(map(str, results)))


if __name__ == "__main__":
    main()
