"""TPC-H benchmark runner (rebuild of benchmarks/src/bin/tpch.rs).

Modes:
  python benchmarks/tpch.py data --scale 1 --out /tmp/tpch_sf1
  python benchmarks/tpch.py run --data /tmp/tpch_sf1 [--query 1] \
      [--engine cpu|tpu] [--mode local|standalone|remote --scheduler H:P] \
      [--iterations 3] [--verify]

`--verify` checks results against the pandas oracle (the reference's
expected-results verification leg).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def q_path(n: int) -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), "tpch", "queries", f"q{n}.sql")


def cmd_data(args) -> None:
    from ballista_tpu.testing.tpchgen import generate_tpch

    t0 = time.time()
    generate_tpch(args.out, scale=args.scale, seed=args.seed, files_per_table=args.files_per_table)
    print(f"generated sf={args.scale} at {args.out} in {time.time() - t0:.1f}s")


def cmd_run(args) -> None:
    from ballista_tpu.client.context import SessionContext
    from ballista_tpu.config import BallistaConfig, DEFAULT_SHUFFLE_PARTITIONS, EXECUTOR_ENGINE, TARGET_PARTITIONS
    from ballista_tpu.testing.tpchgen import register_tpch

    cfg = BallistaConfig({
        EXECUTOR_ENGINE: args.engine,
        TARGET_PARTITIONS: args.partitions,
        DEFAULT_SHUFFLE_PARTITIONS: args.shuffle_partitions,
    })
    if args.mode == "local":
        ctx = SessionContext(cfg)
    elif args.mode == "standalone":
        ctx = SessionContext.standalone(cfg, num_executors=args.executors, vcores=args.concurrency)
    else:
        ctx = SessionContext.remote(args.scheduler, cfg)
    register_tpch(ctx, args.data)

    queries = [args.query] if args.query else list(range(1, 23))
    ref_tables = None
    if args.verify:
        from ballista_tpu.testing.reference import load_tables

        ref_tables = load_tables(args.data)

    results = {}
    total = 0.0
    for q in queries:
        sql = open(q_path(q)).read()
        times = []
        out = None
        try:
            for _ in range(args.iterations):
                t0 = time.time()
                out = ctx.sql(sql).collect()
                times.append(time.time() - t0)
            best = min(times)
            total += best
            status = f"{best:8.3f}s  rows={out.num_rows}"
            if ref_tables is not None:
                from ballista_tpu.testing.reference import compare_results, run_reference

                problems = compare_results(out, run_reference(q, ref_tables), q)
                status += "  ✓" if not problems else f"  MISMATCH: {problems[0]}"
            results[f"q{q}"] = round(best, 4)
            print(f"q{q:<3} {status}")
        except Exception as e:  # noqa: BLE001
            print(f"q{q:<3} FAILED: {e}")
            results[f"q{q}"] = None
    print(f"\ntotal (best-of-{args.iterations}): {total:.3f}s  engine={args.engine} mode={args.mode}")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump({"engine": args.engine, "mode": args.mode, "total_s": round(total, 3),
                       "queries": results}, f, indent=1)


def main(argv=None) -> None:
    ap = argparse.ArgumentParser(description="TPC-H benchmark")
    sub = ap.add_subparsers(dest="cmd", required=True)
    d = sub.add_parser("data")
    d.add_argument("--scale", type=float, default=1.0)
    d.add_argument("--out", required=True)
    d.add_argument("--seed", type=int, default=42)
    d.add_argument("--files-per-table", type=int, default=4)
    r = sub.add_parser("run")
    r.add_argument("--data", required=True)
    r.add_argument("--query", type=int, default=None)
    r.add_argument("--engine", choices=("cpu", "tpu"), default="cpu")
    r.add_argument("--mode", choices=("local", "standalone", "remote"), default="local")
    r.add_argument("--scheduler", default="localhost:50050")
    r.add_argument("--executors", type=int, default=1)
    r.add_argument("--concurrency", type=int, default=8)
    r.add_argument("--partitions", type=int, default=8)
    r.add_argument("--shuffle-partitions", type=int, default=16)
    r.add_argument("--iterations", type=int, default=2)
    r.add_argument("--verify", action="store_true")
    r.add_argument("--json-out", default=None)
    args = ap.parse_args(argv)
    if args.cmd == "data":
        cmd_data(args)
    else:
        cmd_run(args)


if __name__ == "__main__":
    main()
